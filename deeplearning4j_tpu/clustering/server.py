"""Nearest-neighbors REST server + client.

Parity: ref deeplearning4j-nearestneighbors-parent/nearestneighbor-server
(NearestNeighborsServer exposing /knn over HTTP with a vectorized index) and
nearestneighbors-client. Built on the shared JSON-HTTP helper; the index is the
XLA brute-force NearestNeighbors (MXU distance block), so each request is one
jitted call. Malformed requests return JSON errors (400), not dropped
connections.
"""
from __future__ import annotations

import json
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.knn import NearestNeighbors
from deeplearning4j_tpu.util.http import JsonHttpServer


class NearestNeighborsServer(JsonHttpServer):
    """(ref server/NearestNeighborsServer.java)"""

    def __init__(self, data, port: int = 0, distance: str = "euclidean"):
        index = NearestNeighbors(data, distance=distance)
        n_points = int(np.asarray(data).shape[0])

        def knn(req: dict):
            k = int(req.get("k", 5))
            if not 1 <= k <= n_points:
                raise ValueError(f"k={k} out of range [1, {n_points}]")
            if "index" in req:   # query by stored point id (ref knn by index)
                i = int(req["index"])
                if not 0 <= i < n_points:
                    raise ValueError(f"index {i} out of range")
                q = np.asarray(index.data[i])
            else:
                q = np.asarray(req["vector"], np.float32)
            dist, idx = index.search(q, k=k)
            return {"indices": idx[0].tolist(), "distances": dist[0].tolist()}

        super().__init__({
            "GET /status": lambda q: {"points": n_points, "ok": True},
            "POST /knn": knn,
        }, port=port)


class NearestNeighborsClient:
    """(ref client/NearestNeighborsClient.java)"""

    def __init__(self, address: str, timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _post(self, path, payload):
        import urllib.request
        req = urllib.request.Request(
            self.address + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def knn(self, vector, k: int = 5) -> dict:
        return self._post("/knn", {"vector": np.asarray(vector).tolist(),
                                   "k": int(k)})
    knnVector = knn

    def knn_by_index(self, index: int, k: int = 5) -> dict:
        return self._post("/knn", {"index": int(index), "k": int(k)})

    def status(self) -> dict:
        import urllib.request
        with urllib.request.urlopen(self.address + "/status",
                                    timeout=self.timeout) as r:
            return json.loads(r.read().decode())
