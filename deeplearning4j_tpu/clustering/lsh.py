"""Random-projection LSH for approximate nearest neighbors.

The 0.9.1 reference has no LSH module (its approximate-neighbor structures are
the VP/KD/sp trees); later DL4J versions grew RandomProjectionLSH — provided
here as the approximate-neighbor provider that composes with the brute-force
KNN (clustering/knn.py) and the t-SNE k-NN stage: signed random projections
(SimHash) bucket vectors across L tables; queries union candidate buckets and
re-rank exactly — one (B, D) x (D, bits) matmul to hash, one small exact top-k
to answer, both MXU-shaped.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np


class RandomProjectionLSH:
    def __init__(self, dims: int, hash_bits: int = 8, num_tables: int = 16,
                 seed: int = 12345):
        self.dims = int(dims)
        self.bits = int(hash_bits)
        self.L = int(num_tables)
        rng = np.random.RandomState(seed)
        # (L, D, bits) signed projection planes
        self._planes = rng.randn(self.L, self.dims, self.bits)
        self._tables: List[Dict[int, List[int]]] = [
            defaultdict(list) for _ in range(self.L)]
        self._data: np.ndarray = np.zeros((0, self.dims), np.float32)

    def _keys(self, x: np.ndarray) -> np.ndarray:
        """(n, L) integer bucket keys via sign bits."""
        bits = (np.einsum("nd,ldb->nlb", x, self._planes) > 0)
        weights = 1 << np.arange(self.bits)
        return (bits * weights).sum(axis=-1)

    def index(self, data) -> "RandomProjectionLSH":
        data = np.asarray(data, np.float32)
        base = self._data.shape[0]
        self._data = np.vstack([self._data, data]) if base else data
        keys = self._keys(data)
        for i in range(data.shape[0]):
            for t in range(self.L):
                self._tables[t][int(keys[i, t])].append(base + i)
        return self

    def candidates(self, query) -> np.ndarray:
        q = np.asarray(query, np.float32).reshape(1, -1)
        keys = self._keys(q)[0]
        cand = set()
        for t in range(self.L):
            cand.update(self._tables[t].get(int(keys[t]), ()))
        return np.fromiter(cand, np.int64, len(cand))

    def search(self, query, k: int = 10) -> List[Tuple[int, float]]:
        """Approximate k-NN: exact re-rank of the union of candidate buckets.
        Returns [(index, distance)] closest first; falls back to brute force
        when the buckets miss (rare, small data)."""
        q = np.asarray(query, np.float32).reshape(-1)
        cand = self.candidates(q)
        if cand.size < k:
            cand = np.arange(self._data.shape[0])
        d = np.linalg.norm(self._data[cand] - q[None, :], axis=1)
        order = np.argsort(d)[:k]
        return [(int(cand[i]), float(d[i])) for i in order]
