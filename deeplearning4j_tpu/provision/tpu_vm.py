"""TPU-VM cluster provisioning (L10 infra glue).

Parity: ref deeplearning4j-aws/.../ec2/Ec2BoxCreator.java (create/blow-away
EC2 boxes for a training cluster) + ec2/provision/HostProvisioner.java /
ClusterSetup.java (ship files + run commands on every box over SSH). The
TPU-native rendering of "provision a training cluster" is TPU-VM slice
management: `gcloud compute tpus tpu-vm create/list/delete`, startup-script
config shipping, and `ssh --worker=all` fan-out — the exact workflow a
multi-host `jax.distributed` run needs (distributed/conf.py consumes the
host list these commands produce).

All cloud interaction goes through an injected `transport` (a callable
`transport(argv) -> (returncode, stdout)`). The default shells out to the
`gcloud` CLI; tests inject a mock transport, so everything here is testable
with zero egress — and the command lines the mock records are exactly what
an operator could paste into a shell.
"""
from __future__ import annotations

import json
import shlex
import subprocess
from typing import Callable, List, Optional, Sequence, Tuple

Transport = Callable[[Sequence[str]], Tuple[int, str]]


def gcloud_transport(argv: Sequence[str]) -> Tuple[int, str]:
    """Default transport: run the real gcloud CLI (requires it installed and
    authenticated; never exercised by the test suite)."""
    proc = subprocess.run(list(argv), capture_output=True, text=True)
    return proc.returncode, proc.stdout or proc.stderr


class ProvisioningError(RuntimeError):
    pass


class TpuVmCreator:
    """(ref ec2/Ec2BoxCreator.java — create()/createSpot()/blowAway()) —
    creates, lists, and deletes TPU-VM slices.

    accelerator_type is the 'instance size' analog (v5litepod-8 ...);
    runtime_version the AMI analog."""

    DEFAULT_RUNTIME = "tpu-ubuntu2204-base"

    def __init__(self, name_prefix: str, num_slices: int,
                 accelerator_type: str, zone: str,
                 runtime_version: str = DEFAULT_RUNTIME,
                 project: Optional[str] = None,
                 startup_script: Optional[str] = None,
                 transport: Transport = gcloud_transport):
        self.name_prefix = str(name_prefix)
        self.num_slices = int(num_slices)
        self.accelerator_type = str(accelerator_type)
        self.zone = str(zone)
        self.runtime_version = str(runtime_version)
        self.project = project
        self.startup_script = startup_script
        self.transport = transport
        self.nodes_created: List[str] = []

    def _base(self) -> List[str]:
        argv = ["gcloud", "compute", "tpus", "tpu-vm"]
        return argv

    def _common(self) -> List[str]:
        argv = [f"--zone={self.zone}"]
        if self.project:
            argv.append(f"--project={self.project}")
        return argv

    def _run(self, argv: Sequence[str]) -> str:
        code, out = self.transport(argv)
        if code != 0:
            raise ProvisioningError(
                f"command failed ({code}): {' '.join(map(str, argv))}\n{out}")
        return out

    def create(self, spot: bool = False) -> List[str]:
        """Create `num_slices` TPU-VM slices (ref Ec2BoxCreator.create();
        spot=True is the createSpot() analog — preemptible capacity)."""
        for i in range(self.num_slices):
            name = f"{self.name_prefix}-{i}"
            argv = self._base() + ["create", name] + self._common() + [
                f"--accelerator-type={self.accelerator_type}",
                f"--version={self.runtime_version}"]
            if spot:
                argv.append("--spot")
            if self.startup_script is not None:
                argv.append(
                    "--metadata=startup-script=" + self.startup_script)
            self._run(argv)
            self.nodes_created.append(name)
        return list(self.nodes_created)

    def create_spot(self) -> List[str]:
        return self.create(spot=True)
    createSpot = create_spot

    def list_nodes(self) -> List[dict]:
        """All slices in the zone, as parsed JSON (name/state/endpoints)."""
        out = self._run(self._base() + ["list", "--format=json"]
                        + self._common())
        return json.loads(out) if out.strip() else []

    def hosts(self) -> List[str]:
        """Worker endpoint IPs of the slices this creator made — the
        coordinator address list a jax.distributed run consumes
        (ref Ec2BoxCreator.getHosts())."""
        ips = []
        mine = set(self.nodes_created)
        for node in self.list_nodes():
            # exact last-path-segment match: endswith would also claim a
            # foreign 'retrain-0' for our 'train-0'
            if node.get("name", "").split("/")[-1] in mine:
                for ep in node.get("networkEndpoints", []):
                    ip = ep.get("ipAddress")
                    if ip:
                        ips.append(ip)
        return ips
    getHosts = hosts

    def blow_away(self) -> None:
        """Delete every slice this creator made (ref Ec2BoxCreator.blowAway)."""
        for name in self.nodes_created:
            self._run(self._base() + ["delete", name, "--quiet"]
                      + self._common())
        self.nodes_created = []
    blowAway = blow_away


class ClusterSetup:
    """(ref ec2/provision/ClusterSetup.java + HostProvisioner.java — upload
    artifacts and run commands on every box over SSH) — the TPU-VM analogs
    are `tpu-vm scp` and `tpu-vm ssh --worker=all`."""

    def __init__(self, creator: TpuVmCreator):
        self.creator = creator

    def _each_node(self):
        if not self.creator.nodes_created:
            raise ProvisioningError("no nodes created yet")
        return list(self.creator.nodes_created)

    def upload(self, local_path: str, remote_path: str = "~/") -> None:
        """Ship a file to every worker of every slice (HostProvisioner
        .uploadAndRun's scp half; config-as-JSON shipping rides this)."""
        for name in self._each_node():
            self.creator._run(
                self.creator._base() + [
                    "scp", local_path, f"{name}:{remote_path}",
                    "--worker=all"] + self.creator._common())

    def run_on_all(self, command: str) -> List[str]:
        """Run a shell command on every worker of every slice (ref
        HostProvisioner.runRemoteCommand)."""
        outs = []
        for name in self._each_node():
            outs.append(self.creator._run(
                self.creator._base() + [
                    "ssh", name, "--worker=all",
                    f"--command={command}"] + self.creator._common()))
        return outs

    def launch_distributed(self, script_path: str,
                           env: Optional[dict] = None,
                           log_file: str = "dl4jtpu_train.log") -> List[str]:
        """Upload a training script and start it on all workers — the
        DistributedDeepLearningTrainer.java entry-point analog. JAX's TPU-VM
        runtime wires process_id/coordinator automatically, so plain
        `python script` on every worker forms the jax.distributed world.

        The command is BACKGROUNDED (nohup ... &) so the ssh returns
        immediately on every slice: the jax.distributed world needs all
        slices' processes alive simultaneously — a blocking sequential
        launch would deadlock slice 0 waiting for slice 1 to join."""
        self.upload(script_path)
        exports = "".join(f"export {k}={shlex.quote(str(v))} && "
                          for k, v in (env or {}).items())
        base = script_path.rsplit("/", 1)[-1]
        inner = f"{exports}python3 {base}"
        return self.run_on_all(
            f"nohup sh -c {shlex.quote(inner)} > {log_file} 2>&1 &")
