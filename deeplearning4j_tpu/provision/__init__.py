"""Cluster provisioning + object-storage transfer (L10 infra glue).

TPU-native rendering of the reference's deeplearning4j-aws module: EC2 box
creation becomes TPU-VM slice management (tpu_vm.py), S3 transfer becomes
GCS transfer behind the same API shapes (gcs.py). All cloud interaction is
transport-injected, so the module is fully testable with zero egress.
"""
from deeplearning4j_tpu.provision.gcs import (
    GcsDownloader, GcsTransport, GcsUploader, GsutilTransport,
    InMemoryGcsTransport)
from deeplearning4j_tpu.provision.tpu_vm import (
    ClusterSetup, ProvisioningError, TpuVmCreator, gcloud_transport)

__all__ = [
    "TpuVmCreator", "ClusterSetup", "ProvisioningError", "gcloud_transport",
    "GcsDownloader", "GcsUploader", "GcsTransport", "GsutilTransport",
    "InMemoryGcsTransport",
]
