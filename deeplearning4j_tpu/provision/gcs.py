"""GCS object-storage transfer (L10 infra glue).

Parity: ref deeplearning4j-aws/.../s3/reader/S3Downloader.java +
s3/uploader/S3Uploader.java (+ BaseS3 session plumbing) — move datasets and
checkpoints between the training cluster and object storage. The TPU-native
rendering targets Google Cloud Storage with the SAME API shapes
(keysForBucket / iterateBucket / objectForKey / download / downloadFolder;
upload / multiPartUpload / uploadFolder / uploadFileList), so reference
users find the operations where they expect them.

Storage access goes through a `GcsTransport`; the default shells out to
`gsutil`, and `InMemoryGcsTransport` backs the zero-egress tests (and doubles
as a local fake for development). Checkpoint zips from
util/model_serializer.py are plain files, so CheckpointListener output can
ride `GcsUploader.upload_folder` directly.
"""
from __future__ import annotations

import io
import os
import subprocess
from typing import Callable, Dict, Iterator, List, Optional


class GcsTransport:
    """Minimal storage verbs the up/downloaders need."""

    def list_buckets(self) -> List[str]:
        raise NotImplementedError

    def list_keys(self, bucket: str, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def get(self, bucket: str, key: str) -> bytes:
        raise NotImplementedError

    def put(self, bucket: str, key: str, data: bytes) -> None:
        raise NotImplementedError

    def compose(self, bucket: str, part_keys: List[str],
                dest_key: str) -> None:
        """Server-side concatenation of parts into dest (GCS compose)."""
        raise NotImplementedError

    def delete(self, bucket: str, key: str) -> None:
        raise NotImplementedError


class GsutilTransport(GcsTransport):
    """Default transport: the gsutil CLI (requires install + auth; never
    exercised by the test suite)."""

    def _run(self, argv, data: Optional[bytes] = None) -> bytes:
        proc = subprocess.run(argv, input=data, capture_output=True)
        if proc.returncode != 0:
            raise RuntimeError(f"gsutil failed: {' '.join(argv)}: "
                               f"{proc.stderr.decode(errors='replace')}")
        return proc.stdout

    def list_buckets(self):
        out = self._run(["gsutil", "ls"]).decode()
        return [l.removeprefix("gs://").rstrip("/")
                for l in out.splitlines() if l.startswith("gs://")]

    def list_keys(self, bucket, prefix=""):
        out = self._run(["gsutil", "ls", "-r",
                         f"gs://{bucket}/{prefix}**"]).decode()
        pre = f"gs://{bucket}/"
        return [l.removeprefix(pre) for l in out.splitlines()
                if l.startswith(pre) and not l.endswith("/")]

    def get(self, bucket, key):
        return self._run(["gsutil", "cp", f"gs://{bucket}/{key}", "-"])

    def put(self, bucket, key, data):
        self._run(["gsutil", "cp", "-", f"gs://{bucket}/{key}"], data=data)

    def compose(self, bucket, part_keys, dest_key):
        self._run(["gsutil", "compose"]
                  + [f"gs://{bucket}/{k}" for k in part_keys]
                  + [f"gs://{bucket}/{dest_key}"])

    def delete(self, bucket, key):
        self._run(["gsutil", "rm", f"gs://{bucket}/{key}"])


class InMemoryGcsTransport(GcsTransport):
    """Dict-backed fake for tests / local development."""

    def __init__(self):
        self.store: Dict[str, Dict[str, bytes]] = {}

    def list_buckets(self):
        return sorted(self.store)

    def list_keys(self, bucket, prefix=""):
        return sorted(k for k in self.store.get(bucket, {})
                      if k.startswith(prefix))

    def get(self, bucket, key):
        try:
            return self.store[bucket][key]
        except KeyError:
            raise FileNotFoundError(f"gs://{bucket}/{key}")

    def put(self, bucket, key, data):
        self.store.setdefault(bucket, {})[key] = bytes(data)

    def compose(self, bucket, part_keys, dest_key):
        # the real GCS compose rejects >32 components — the fake must too,
        # or tests would pass code that fails in production
        if len(part_keys) > 32:
            raise ValueError(
                f"compose takes at most 32 components, got {len(part_keys)}")
        self.store.setdefault(bucket, {})[dest_key] = b"".join(
            self.store[bucket][k] for k in part_keys)

    def delete(self, bucket, key):
        self.store.get(bucket, {}).pop(key, None)


class GcsDownloader:
    """(ref s3/reader/S3Downloader.java API shape)."""

    def __init__(self, transport: Optional[GcsTransport] = None):
        self.transport = transport or GsutilTransport()

    def buckets(self) -> List[str]:
        return self.transport.list_buckets()

    def keys_for_bucket(self, bucket: str) -> List[str]:
        return self.transport.list_keys(bucket)
    keysForBucket = keys_for_bucket

    def object_for_key(self, bucket: str, key: str) -> io.BytesIO:
        return io.BytesIO(self.transport.get(bucket, key))
    objectForKey = object_for_key

    def iterate_bucket(self, bucket: str) -> Iterator[io.BytesIO]:
        for key in self.keys_for_bucket(bucket):
            yield self.object_for_key(bucket, key)
    iterateBucket = iterate_bucket

    def paginate(self, bucket: str,
                 listener: Callable[[str], None]) -> None:
        """(ref S3Downloader.paginate + BucketKeyListener) — callback per key."""
        for key in self.keys_for_bucket(bucket):
            listener(key)

    def download(self, bucket: str, key: str, to) -> None:
        """`to`: a path or a writable binary file object."""
        data = self.transport.get(bucket, key)
        if hasattr(to, "write"):
            to.write(data)
        else:
            with open(to, "wb") as f:
                f.write(data)

    def download_folder(self, bucket: str, key_prefix: str,
                        folder_path: str) -> List[str]:
        """(ref S3Downloader.downloadFolder) — every object under the prefix
        lands under folder_path with its relative key path."""
        written = []
        for key in self.transport.list_keys(bucket, key_prefix):
            rel = key[len(key_prefix):].lstrip("/")
            dest = os.path.join(folder_path, rel or os.path.basename(key))
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            self.download(bucket, key, dest)
            written.append(dest)
        return written
    downloadFolder = download_folder


class GcsUploader:
    """(ref s3/uploader/S3Uploader.java API shape)."""

    MULTIPART_CHUNK = 8 * 1024 * 1024

    def __init__(self, transport: Optional[GcsTransport] = None):
        self.transport = transport or GsutilTransport()

    def upload(self, file_path: str, bucket: str,
               name: Optional[str] = None) -> None:
        """upload(file, bucket) | upload(file, bucket, name) (the reference's
        two overloads)."""
        key = name or os.path.basename(file_path)
        with open(file_path, "rb") as f:
            self.transport.put(bucket, key, f.read())

    def multi_part_upload(self, file_path: str, bucket: str,
                          name: Optional[str] = None) -> int:
        """(ref S3Uploader.multiPartUpload) — true chunked streaming: each
        part is PUT as it is read (peak memory = one chunk), then composed
        server-side into the destination and the parts deleted. Returns the
        number of parts sent."""
        key = name or os.path.basename(file_path)
        part_keys = []
        with open(file_path, "rb") as f:
            while True:
                chunk = f.read(self.MULTIPART_CHUNK)
                if not chunk:
                    break
                pk = f"{key}.part{len(part_keys)}"
                self.transport.put(bucket, pk, chunk)
                part_keys.append(pk)
        if not part_keys:  # empty file: one empty object
            self.transport.put(bucket, key, b"")
            return 1
        n_parts = len(part_keys)
        # GCS compose takes at most 32 components per call; fold larger
        # uploads in <=32-wide rounds (composites may be re-composed), the
        # final round composing STRAIGHT into the destination key
        round_ = 0
        while len(part_keys) > 32:
            next_keys = []
            for gi in range(0, len(part_keys), 32):
                group = part_keys[gi:gi + 32]
                if len(group) == 1:
                    next_keys.append(group[0])
                    continue
                ck = f"{key}.compose{round_}.{gi // 32}"
                self.transport.compose(bucket, group, ck)
                for pk in group:
                    self.transport.delete(bucket, pk)
                next_keys.append(ck)
            part_keys = next_keys
            round_ += 1
        self.transport.compose(bucket, part_keys, key)
        for pk in part_keys:
            if pk != key:
                self.transport.delete(bucket, pk)
        return n_parts
    multiPartUpload = multi_part_upload

    def upload_folder(self, bucket: str, key_prefix: str,
                      folder_path: str) -> List[str]:
        """(ref S3Uploader.uploadFolder) — recursive, keys mirror the tree."""
        keys = []
        for root, _, files in os.walk(folder_path):
            for fn in sorted(files):
                full = os.path.join(root, fn)
                rel = os.path.relpath(full, folder_path)
                key = f"{key_prefix.rstrip('/')}/{rel}" if key_prefix else rel
                self.upload(full, bucket, key)
                keys.append(key)
        return keys
    uploadFolder = upload_folder

    def upload_file_list(self, bucket: str, folder_path: str,
                         file_list: List[str],
                         key_prefix: str = "") -> List[str]:
        """(ref S3Uploader.uploadFileList)."""
        keys = []
        for fn in file_list:
            full = os.path.join(folder_path, fn)
            key = f"{key_prefix.rstrip('/')}/{fn}" if key_prefix else fn
            self.upload(full, bucket, key)
            keys.append(key)
        return keys
    uploadFileList = upload_file_list
